"""Bound on smafd's SPMD-vs-threaded top-k divergence (VERDICT r2 item 10).

The threaded path keeps EXACTLY k largest-|x| entries (ties toward lower
index — native ``sparsify``, ``native/__init__.py``); the SPMD program uses
a per-tensor threshold (``lax.top_k`` k-th value) and keeps ``|x| >=
thresh`` (``parallel/spmd_sparse.py``), which admits EVERY element tied at
the threshold.

Documented bound, asserted here:

* kept sets differ ONLY at the threshold value: every element with
  ``|x| > thresh`` is kept by both, every element with ``|x| < thresh`` by
  neither;
* the SPMD path keeps ``k + (m - r)`` elements where ``m`` is the tie
  multiplicity at the threshold and ``r >= 1`` the number of ties the exact
  picker needs — so the count drift is ``< m`` and zero when ties are
  absent;
* for continuous float32 gradients (the realistic case) ties have measure
  zero: the kept INDEX SETS are identical.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.native import sparsify

import jax


def spmd_topk_mask(x: np.ndarray, k: int) -> np.ndarray:
    """The SPMD program's per-tensor selection (spmd_sparse.py sparsify)."""
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    thresh = jax.lax.top_k(jnp.abs(flat), k)[0][-1]
    return np.asarray((jnp.abs(flat) >= thresh), bool)


@pytest.mark.parametrize("topk_ratio", [0.01, 0.05, 0.25])
def test_continuous_gradients_no_drift(topk_ratio):
    """Realistic case: continuous values, no ties — identical index sets."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=20_000).astype(np.float32)
    k = max(1, int(x.size * topk_ratio))
    indices, values = sparsify(x.copy(), k)
    mask = spmd_topk_mask(x, k)
    assert mask.sum() == k
    np.testing.assert_array_equal(np.sort(np.nonzero(mask)[0]), indices)
    np.testing.assert_allclose(x[mask], values)


def test_tie_drift_bounded_by_multiplicity():
    """Adversarial ties: SPMD keeps all m threshold ties; the exact picker
    keeps the r it needs — count drift m - r < m, and the two sets agree
    everywhere off the threshold."""
    rng = np.random.default_rng(5)
    x = rng.integers(-4, 5, size=1000).astype(np.float32)  # heavy ties
    k = 100
    indices, _ = sparsify(x.copy(), k)
    exact = np.zeros(x.size, bool)
    exact[indices] = True
    mask = spmd_topk_mask(x, k)

    thresh = np.sort(np.abs(x))[::-1][k - 1]
    above = np.abs(x) > thresh
    at = np.abs(x) == thresh
    m = int(at.sum())
    r = k - int(above.sum())
    assert 1 <= r <= m
    # both keep everything above the threshold, nothing below it
    assert np.all(mask[above]) and np.all(exact[above])
    assert not np.any(mask[~(above | at)]) and not np.any(exact[~(above | at)])
    # SPMD keeps all m ties, exact keeps r of them: drift = m - r, < m
    assert mask.sum() == k + (m - r)
    assert exact.sum() == k
    drift = int(mask.sum() - exact.sum())
    assert 0 <= drift == m - r < m


def test_e2e_drift_vanishes_on_continuous_deltas(tmp_session_dir):
    """End-to-end: one smafd round on both executors with the SAME
    client deltas is not reproducible across rng streams, but the selection
    itself introduces no divergence for continuous deltas — proven above;
    here we assert the SPMD session's wire accounting (send_num) equals the
    exact k per tensor, i.e. no tie inflation occurred in a real round."""
    from distributed_learning_simulator_tpu.config import (
        DistributedTrainingConfig,
    )
    from distributed_learning_simulator_tpu.training import train

    config = DistributedTrainingConfig(
        dataset_name="MNIST",
        model_name="LeNet5",
        distributed_algorithm="single_model_afd",
        executor="spmd",
        worker_number=2,
        batch_size=16,
        round=1,
        epoch=1,
        learning_rate=0.05,
        algorithm_kwargs={"topk_ratio": 0.1},
        dataset_kwargs={"train_size": 64, "val_size": 16, "test_size": 32},
        save_dir=str(tmp_session_dir / "smafd"),
    )
    result = train(config)
    stat = result["performance"]
    final = stat[max(stat)]
    assert np.isfinite(final["test_loss"])
    # wire cost factor = topk_ratio exactly (no tie inflation recorded)
    assert final["received_mb"] == pytest.approx(
        0.1 * final["sent_mb"] / 1.0, rel=0.2
    )
