"""Streamed populations (``algorithm_kwargs.population_store: streamed``):
the host-offloaded per-client state store, the double-buffered cohort
prefetcher, and their session wiring (util/population.py + the FedAvg
family's streamed round path).

The acceptance contract mirrors selection gather's (PR 3): streaming is a
pure PLACEMENT change — the cohort-shaped programs are the same
shape-polymorphic dense programs traced at ``s_pad`` and the per-client
rng streams are fold_in-indexed by worker id, so the trajectory must be
bit-identical to the device-resident path, per-round and fused-horizon,
composing with dropout weight rows and the OBD phase programs.  On top of
that sit the streamed-only contracts: writeback durability across a
kill/resume (via the ``util/resume.py`` torn-store rules), never-selected
clients keeping fresh-init state in the sparse opt store, and LOUD
rejection wherever the knob cannot apply.
"""

import glob
import os

import numpy as np
import pytest

from conftest import fed_avg_config
from distributed_learning_simulator_tpu.parallel.mesh import (
    broadcast_selection_rows,
    create_hybrid_device_mesh,
    make_mesh,
)
from distributed_learning_simulator_tpu.training import (
    _build_task,
    train,
    train_with_recovery,
)
from distributed_learning_simulator_tpu.util.population import (
    CohortPrefetcher,
    PopulationStore,
    WritebackQueue,
    union_cohort,
)


# ---------------------------------------------------------------------------
# fast unit layer: the store / prefetcher / cohort primitives


def test_dense_store_fetch_writeback_roundtrip():
    tree = {
        "w": np.arange(24, dtype=np.float32).reshape(6, 4),
        "b": np.arange(6, dtype=np.int32),
    }
    store = PopulationStore.from_stacked(tree)
    assert store.n_slots == 6
    got = store.fetch([4, 1])
    np.testing.assert_array_equal(got["w"], tree["w"][[4, 1]])
    np.testing.assert_array_equal(got["b"], tree["b"][[4, 1]])
    # fetch returns fresh arrays — mutating them must not leak back
    got["w"][:] = -1.0
    assert store.fetch([4])["w"][0, 0] == 16.0
    store.writeback([1, 3], {"w": np.zeros((2, 4), np.float32), "b": np.array([7, 8], np.int32)})
    np.testing.assert_array_equal(store.fetch([1])["w"][0], np.zeros(4))
    assert store.fetch([3])["b"][0] == 8
    assert store.row_nbytes == 4 * 4 + 4
    assert store.nbytes == 6 * store.row_nbytes


def test_sparse_store_never_written_is_fresh_init():
    """The lazy-store contract the OBD opt population rides: an id that
    was never written fetches the default row, and only written ids are
    materialized (host RAM scales with participants, not population)."""
    default = {"m": np.full((3,), 0.5, np.float32), "count": np.int32(0)}
    store = PopulationStore.lazy(lambda: default, n_slots=1_000_000)
    assert store.materialized_ids() == []
    got = store.fetch([0, 999_999])
    np.testing.assert_array_equal(got["m"], np.broadcast_to(0.5, (2, 3)))
    store.writeback([7], {"m": np.ones((1, 3), np.float32), "count": np.array([4], np.int32)})
    assert store.materialized_ids() == [7]
    mixed = store.fetch([7, 8])
    np.testing.assert_array_equal(mixed["m"][0], np.ones(3))
    np.testing.assert_array_equal(mixed["m"][1], np.full(3, 0.5))
    assert mixed["count"][0] == 4 and mixed["count"][1] == 0
    # nbytes counts materialized rows only — the million-slot store did
    # not allocate a million rows
    assert store.nbytes == store.row_nbytes


def test_store_save_load_roundtrip_and_tag(tmp_path):
    tree = {"w": np.random.default_rng(0).normal(size=(10, 3)).astype(np.float32)}
    store = PopulationStore.from_stacked(tree)
    directory = str(tmp_path / "pop")
    store.save(directory, chunk_slots=4, tag=3)
    assert len(glob.glob(os.path.join(directory, "pop_*.npz"))) == 3
    loaded = PopulationStore.load(directory, expect_tag=3)
    assert loaded is not None and loaded.n_slots == 10
    (leaf,) = loaded.fetch(np.arange(10)).values()
    np.testing.assert_array_equal(leaf, tree["w"])
    # wrong tag / absent directory -> None (fresh-state fallback), never a
    # crash — the util/resume.py durable-or-absent rule
    assert PopulationStore.load(directory, expect_tag=4) is None
    assert PopulationStore.load(str(tmp_path / "missing")) is None


def test_store_torn_chunk_loads_as_none(tmp_path):
    store = PopulationStore.from_stacked({"w": np.ones((8, 2), np.float32)})
    directory = str(tmp_path / "pop")
    store.save(directory, chunk_slots=4, tag=1)
    chunk = sorted(glob.glob(os.path.join(directory, "pop_*.npz")))[0]
    with open(chunk, "wb") as f:
        f.write(b"not an npz")
    assert PopulationStore.load(directory, expect_tag=1) is None
    # torn MANIFEST (killed mid-json) is equally a fresh-state fallback
    manifest = os.path.join(directory, "population_manifest.json")
    with open(manifest, "w", encoding="utf8") as f:
        f.write('{"version": 1, "n_slo')
    assert PopulationStore.load(directory) is None


def test_sparse_restore_rematerializes_only_nondefault_rows(tmp_path):
    default = {"m": np.zeros((2,), np.float32)}
    store = PopulationStore.lazy(lambda: default, n_slots=6)
    store.writeback([2], {"m": np.array([[1.0, 2.0]], np.float32)})
    directory = str(tmp_path / "opt")
    store.save(directory, tag=2)
    restored = PopulationStore.load(directory, default_row=lambda: default, expect_tag=2)
    assert restored is not None
    # rows equal to the default stay UNmaterialized — the restored store
    # keeps the fresh-init-until-written semantics
    assert restored.materialized_ids() == [2]
    np.testing.assert_array_equal(restored.fetch([2])["m"][0], [1.0, 2.0])


def test_union_cohort_positions_and_padding():
    id_rows = np.array([[3, 5, 3], [5, 9, 3]], np.int32)
    union_ids, pos_rows = union_cohort(id_rows, pad_to=5)
    np.testing.assert_array_equal(union_ids, [3, 5, 9, 3, 3])
    # every (round, slot) position indexes its id's row in the union
    np.testing.assert_array_equal(union_ids[pos_rows], id_rows)
    assert pos_rows.dtype == np.int32
    with pytest.raises(ValueError, match="exceeds pad_to"):
        union_cohort(np.array([[0, 1], [2, 3]]), pad_to=3)


def test_prefetcher_overlap_and_mismatch_fallback():
    calls = []

    def fetch(ids):
        calls.append(np.asarray(ids).copy())
        return {"ids": np.asarray(ids)}, int(np.asarray(ids).nbytes)

    prefetcher = CohortPrefetcher(fetch)
    try:
        # cold take (no schedule): synchronous, reported non-prefetched —
        # the telemetry's warmup marker
        placed, stats = prefetcher.take(1, np.array([0, 1]))
        assert not stats.prefetched and stats.exposed == stats.seconds
        np.testing.assert_array_equal(placed["ids"], [0, 1])
        # scheduled take: the background fetch is reused
        prefetcher.schedule(2, np.array([2, 3]))
        placed, stats = prefetcher.take(2, np.array([2, 3]))
        assert stats.prefetched and stats.nbytes == 16
        np.testing.assert_array_equal(placed["ids"], [2, 3])
        # ids mismatch (cannot happen for deterministic selection, but
        # checked anyway): refetch synchronously, never serve stale rows
        prefetcher.schedule(3, np.array([4, 5]))
        placed, stats = prefetcher.take(3, np.array([6, 7]))
        assert not stats.prefetched
        np.testing.assert_array_equal(placed["ids"], [6, 7])
    finally:
        prefetcher.close()


def test_writeback_queue_drains_and_reports_timings():
    store = PopulationStore.from_stacked({"w": np.zeros((4, 2), np.float32)})
    queue = WritebackQueue(store)
    try:
        queue.submit(np.array([1, 2]), {"w": np.ones((2, 2), np.float32)}, round=5)
        queue.drain()
        np.testing.assert_array_equal(store.fetch([1, 2])["w"], np.ones((2, 2)))
        np.testing.assert_array_equal(store.fetch([0])["w"], np.zeros((1, 2)))
        (record,) = queue.pop_completed()
        assert record["round"] == 5 and record["seconds"] >= 0.0
        assert queue.pop_completed() == []
    finally:
        queue.close()


def test_broadcast_selection_rows_single_process_noop():
    rows = np.arange(6).reshape(2, 3)
    np.testing.assert_array_equal(broadcast_selection_rows(rows), rows)


def test_hybrid_mesh_virtual_hosts_matches_flat_grid():
    """The CI seam: ``virtual_hosts`` carves contiguous per-host blocks
    that preserve device order, so the hybrid grid is bit-identical to
    ``make_mesh``'s — the emulated multihost harness depends on it."""
    for model_parallel in (1, 2):
        hybrid = create_hybrid_device_mesh(
            model_parallel=model_parallel, virtual_hosts=2
        )
        flat = make_mesh(model_parallel=model_parallel)
        assert hybrid.axis_names == ("clients", "model")
        assert (hybrid.devices == flat.devices).all()
    with pytest.raises(AssertionError):
        create_hybrid_device_mesh(virtual_hosts=3)  # 8 % 3 != 0


def test_calibration_key_pins_population_store():
    """A calibration taken on the device-resident layout must NEVER
    silently hit on the streamed one (different chunking trade-off)."""
    from distributed_learning_simulator_tpu.util.calibration import (
        calibration_key,
    )

    common = dict(
        session="SpmdFedAvgSession",
        model_name="LeNet5",
        mesh_shape={"clients": 8, "model": 1},
        n_slots=8,
        s_pad=8,
        batch_size=16,
    )
    device_key = calibration_key(**common)
    streamed_key = calibration_key(**common, population_store="streamed")
    assert device_key.endswith("|pop=device")
    assert streamed_key.endswith("|pop=streamed")
    assert device_key != streamed_key


def test_capability_gates_reject_unsupported_sessions():
    """The knob is implemented on the client-axis FedAvg family; every
    other layout must reject it with a reason (consumed by
    tools/shardcheck's conf validator) instead of silently keeping state
    resident."""
    from distributed_learning_simulator_tpu.parallel.spmd import (
        SpmdFedAvgSession,
        SpmdSignSGDSession,
    )
    from distributed_learning_simulator_tpu.parallel.spmd_ep import (
        SpmdExpertParallelSession,
    )
    from distributed_learning_simulator_tpu.parallel.spmd_obd import (
        SpmdFedOBDSession,
    )
    from distributed_learning_simulator_tpu.parallel.spmd_pp import (
        SpmdPipelineSession,
    )
    from distributed_learning_simulator_tpu.parallel.spmd_sparse import (
        SpmdFedDropoutAvgSession,
        SpmdSMAFDSession,
    )

    supported = (SpmdFedAvgSession, SpmdSignSGDSession, SpmdFedOBDSession)
    for cls in supported:
        assert cls.capability_gates()["population_store"] is None, cls
    unsupported = (
        SpmdFedDropoutAvgSession,
        SpmdSMAFDSession,
        SpmdExpertParallelSession,
        SpmdPipelineSession,
    )
    for cls in unsupported:
        reason = cls.capability_gates()["population_store"]
        assert reason, cls
        assert cls.__name__ in reason


# ---------------------------------------------------------------------------
# session layer: parity, durability, and loud runtime rejection (heavy e2e
# — excluded from the tier-1 budget, still run in a plain `pytest tests/`)


def _pop_config(store, save_dir, rounds=3, horizon=1, k=4, workers=8, **overrides):
    """The proven streamed-parity recipe: 8 workers on the 8-device test
    mesh (one slot per device — see the bit-exactness note in
    test_selection_gather.py), an active 4-of-8 selection, tiny MNIST."""
    algorithm_kwargs = dict(overrides.pop("algorithm_kwargs", {}))
    algorithm_kwargs["population_store"] = store
    if k is not None:
        algorithm_kwargs.setdefault("random_client_number", k)
    if horizon != 1:
        algorithm_kwargs["round_horizon"] = horizon
    config = fed_avg_config(
        executor="spmd",
        worker_number=workers,
        round=rounds,
        batch_size=16,
        epoch=1,
        dataset_kwargs={"train_size": 128, "val_size": 16, "test_size": 32},
        algorithm_kwargs=algorithm_kwargs,
        save_dir=save_dir,
        log_file=os.path.join(save_dir, "run.log"),
        **overrides,
    )
    config.load_config_and_process()
    return config


def _final_params(save_dir, round_number):
    path = os.path.join(save_dir, "aggregated_model", f"round_{round_number}.npz")
    with np.load(path) as blob:
        return {k: blob[k] for k in blob.files}


def _assert_bit_exact(device, streamed, device_dir, streamed_dir, rounds):
    assert set(device["performance"]) == set(streamed["performance"])
    for rn in sorted(device["performance"]):
        a, b = device["performance"][rn], streamed["performance"][rn]
        assert a["test_accuracy"] == b["test_accuracy"], (rn, a, b)
        assert a["test_loss"] == b["test_loss"], (rn, a, b)
    pa = _final_params(device_dir, rounds)
    pb = _final_params(streamed_dir, rounds)
    assert pa.keys() == pb.keys()
    for key in pa:
        np.testing.assert_array_equal(pa[key], pb[key], err_msg=key)


@pytest.mark.slow
def test_streamed_vs_device_bit_exact_per_round(tmp_session_dir):
    """The acceptance pin, H=1: the streamed path trains the placed
    s_pad=8 cohort (4 selected + padding) from host-fetched rows and must
    reproduce the device-resident trajectory bit-exactly."""
    device = train(_pop_config("device", "dev"))
    streamed = train(_pop_config("streamed", "str"))
    _assert_bit_exact(device, streamed, "dev", "str", rounds=3)


@pytest.mark.slow
def test_streamed_vs_gather_bit_exact(tmp_session_dir):
    """Streaming vs the device-resident GATHER path: both run the same
    s_pad-shaped program over the same fold_in-by-id rng rows — the
    placement (host fetch vs device take) is the only difference."""
    gathered = train(
        _pop_config(
            "device", "gat", algorithm_kwargs={"selection_gather": True}
        )
    )
    streamed = train(_pop_config("streamed", "sg"))
    _assert_bit_exact(gathered, streamed, "gat", "sg", rounds=3)


@pytest.mark.slow
def test_streamed_fused_horizon_union_cohort_parity(tmp_session_dir):
    """H=4 round fusion: the chunk places ONE union cohort for its
    [H, S_pad] id matrix and the in-program position rows re-select each
    round's slots — bit-exact vs the device-resident fused path."""
    device = train(_pop_config("device", "dh", rounds=4, horizon=4))
    streamed = train(_pop_config("streamed", "sh", rounds=4, horizon=4))
    _assert_bit_exact(device, streamed, "dh", "sh", rounds=4)


@pytest.mark.slow
def test_streamed_dropout_weight_rows_parity(tmp_session_dir):
    """Fault-tolerance dropout rides the host-built weight rows on both
    paths (a dropped client's padded row contributes exact zeros), so the
    composed trajectory stays bit-exact."""
    faults = {"dropout_schedule": {2: [0, 5]}}
    device = train(_pop_config("device", "fd", fault_tolerance=faults))
    streamed = train(_pop_config("streamed", "fs", fault_tolerance=faults))
    _assert_bit_exact(device, streamed, "fd", "fs", rounds=3)


@pytest.mark.slow
@pytest.mark.parametrize("horizon", [1, 3])
def test_sign_sgd_streamed_parity(horizon, tmp_session_dir):
    """sign_SGD streams its per-client batch stacks and host-rng rows the
    same way (votes are small-integer sign sums: exact under the placed
    cohort)."""
    rounds = 3 if horizon == 3 else 2
    common = dict(
        rounds=rounds,
        horizon=horizon,
        distributed_algorithm="sign_SGD",
        distribute_init_parameters=False,
    )
    device = train(_pop_config("device", f"sd{horizon}", **common))
    streamed = train(_pop_config("streamed", f"ss{horizon}", **common))
    assert set(device["performance"]) == set(streamed["performance"])
    for rn in sorted(device["performance"]):
        a, b = device["performance"][rn], streamed["performance"][rn]
        assert a["test_accuracy"] == b["test_accuracy"], (rn, a, b)
        assert a["test_loss"] == b["test_loss"], (rn, a, b)
    for arm in (f"sd{horizon}", f"ss{horizon}"):
        assert os.path.exists(
            os.path.join(arm, "server", "best_global_model.npz")
        )
    with np.load(os.path.join(f"sd{horizon}", "server", "best_global_model.npz")) as da:
        dev_params = {k: da[k] for k in da.files}
    with np.load(os.path.join(f"ss{horizon}", "server", "best_global_model.npz")) as sa:
        for key in sa.files:
            np.testing.assert_array_equal(dev_params[key], sa[key], err_msg=key)


def _obd_config(store, save_dir, rounds=2, **overrides):
    algorithm_kwargs = {
        "population_store": store,
        "random_client_number": 2,
        "dropout_rate": 0.3,
        "second_phase_epoch": 2,
        "early_stop": False,
        **overrides.pop("algorithm_kwargs", {}),
    }
    config = fed_avg_config(
        distributed_algorithm="fed_obd",
        executor="spmd",
        worker_number=4,
        round=rounds,
        batch_size=16,
        epoch=1,
        dataset_kwargs={"train_size": 128, "val_size": 16, "test_size": 32},
        algorithm_kwargs=algorithm_kwargs,
        endpoint_kwargs={"server": {"weight": 0.01}, "worker": {"weight": 0.01}},
        save_dir=save_dir,
        **overrides,
    )
    config.load_config_and_process()
    return config


@pytest.mark.slow
def test_obd_streamed_parity_across_phase_switch(tmp_session_dir):
    """FedOBD streams BOTH stores (client data + the sparse per-slot opt
    rows); phase 2 materializes the full population at the switch.  The
    whole schedule — 2 dropout rounds + 2 tune epochs — must match the
    device path bit-exactly."""
    device = train(_obd_config("device", "od"))
    streamed = train(_obd_config("streamed", "os"))
    _assert_bit_exact(device, streamed, "od", "os", rounds=4)


@pytest.mark.slow
def test_obd_never_selected_clients_keep_fresh_init_state(tmp_session_dir):
    """The sparse-store contract at session level: entering phase 2, only
    clients that participated in a phase-1 round are materialized; a
    never-selected client's opt row IS the fresh default row."""
    from distributed_learning_simulator_tpu.parallel.spmd_obd import (
        SpmdFedOBDSession,
    )

    config = _obd_config("streamed", str(tmp_session_dir / "fresh"))
    ctx = _build_task(config)
    session = SpmdFedOBDSession(
        ctx.config,
        ctx.dataset_collection,
        ctx.model_ctx,
        ctx.engine,
        ctx.practitioners,
    )
    captured = {}
    original = session._materialize_streamed_phase2

    def capture_then_materialize():
        session._writeback.drain()
        captured["ids"] = session._opt_population.materialized_ids()
        return original()

    session._materialize_streamed_phase2 = capture_then_materialize
    session.run()
    assert captured, "phase 2 never materialized the streamed opt store"
    touched = set(captured["ids"])
    # 2 rounds x s_pad=4 cohort rows out of 4 workers: the store holds at
    # most the union of the two cohorts, never the whole-population dense
    # buffer the device path carries
    assert touched <= set(range(session.n_slots))
    assert len(touched) <= 2 * session.s_pad
    untouched = sorted(set(range(session.config.worker_number)) - touched)
    if untouched:
        import jax

        fresh = jax.tree.leaves(session._fresh_opt_row())
        for leaf, expected in zip(
            jax.tree.leaves(session._opt_population.fetch([untouched[0]])),
            fresh,
        ):
            np.testing.assert_array_equal(np.asarray(leaf)[0], expected)


@pytest.mark.slow
def test_obd_streamed_writeback_durable_across_kill_and_resume(tmp_session_dir):
    """Writeback durability: a run killed after phase-1 round 2 resumes
    from the npz-chunked opt store (tag == the resume aggregate).  The
    pin is PARITY UNDER RESUME: the recovered streamed run must match a
    recovered DEVICE-resident run round for round — if the streamed
    store had torn or fallen back fresh, its post-resume momentum would
    diverge from the device path's npz-restored state.  (Post-resume
    rounds are not compared to an UNINTERRUPTED run: OBD resume
    re-derives its phase-2 schedule from the replayed aggregates, a
    pre-existing — and path-independent — continuation semantic.)"""
    faults = {"kill_after_rounds": [2], "restart_backoff_seconds": 0.0}
    device = train_with_recovery(
        _obd_config("device", "kd", rounds=3, fault_tolerance=dict(faults))
    )
    streamed = train_with_recovery(
        _obd_config("streamed", "ks", rounds=3, fault_tolerance=dict(faults))
    )
    assert device["recovery"]["restarts"] == 1
    assert streamed["recovery"]["restarts"] == 1
    # the resume point's store landed durably before the kill
    assert os.path.exists(
        os.path.join(
            "ks", "aggregated_model", "opt_population",
            "population_manifest.json",
        )
    )
    assert set(device["performance"]) == set(streamed["performance"])
    for rn in sorted(device["performance"]):
        a, b = device["performance"][rn], streamed["performance"][rn]
        assert a["test_accuracy"] == b["test_accuracy"], (rn, a, b)
        assert a["test_loss"] == b["test_loss"], (rn, a, b)
    # and the pre-kill rounds restored verbatim from the first attempt
    uninterrupted = train(_obd_config("streamed", "full", rounds=3))
    for rn in (1, 2):
        assert (
            streamed["performance"][rn]["test_loss"]
            == uninterrupted["performance"][rn]["test_loss"]
        ), rn


@pytest.mark.slow
def test_obd_streamed_torn_store_falls_back_fresh(tmp_session_dir):
    """A torn opt-population store at resume (killed mid-save) is a LOUD
    fresh-state fallback, never a crash: the resumed run restores its
    round checkpoints verbatim and completes the full schedule."""
    from distributed_learning_simulator_tpu.util.faults import (
        SimulatedPreemption,
    )

    first = _obd_config(
        "streamed",
        "torn",
        rounds=4,
        fault_tolerance={"kill_after_rounds": [2], "max_restarts": 0},
    )
    with pytest.raises(SimulatedPreemption):
        train(first)
    store_dir = os.path.join("torn", "aggregated_model", "opt_population")
    chunks = sorted(glob.glob(os.path.join(store_dir, "pop_*.npz")))
    assert chunks, "kill landed before the opt store was saved"
    with open(chunks[0], "wb") as f:
        f.write(b"torn mid-write")

    resumed = _obd_config(
        "streamed",
        "torn_resume",
        rounds=4,
        algorithm_kwargs={"resume_dir": first.save_dir},
    )
    result = train(resumed)
    # rounds 1-2 restore verbatim; 3-4 + 2 tune epochs run to completion
    # on fresh opt rows (the documented fallback semantics)
    assert set(result["performance"]) == {1, 2, 3, 4, 5, 6}
    assert result["performance"][3]["phase"] == "block_dropout_rounds"
    assert result["performance"][5]["phase"] == "epoch_tune"


@pytest.mark.slow
def test_streamed_rejected_loudly_where_unsupported(tmp_session_dir):
    """Runtime rejection is a raise naming the knob — never a silent
    device-resident fallback."""
    smafd = _pop_config(
        "streamed",
        "rej_smafd",
        distributed_algorithm="single_model_afd",
        algorithm_kwargs={"dropout_rate": 0.3},
    )
    with pytest.raises(ValueError, match="population_store"):
        train(smafd)

    horizon = _obd_config(
        "streamed", "rej_h", algorithm_kwargs={"round_horizon": 2}
    )
    with pytest.raises(ValueError, match="round_horizon"):
        train(horizon)

    bogus = _pop_config("hostside", "rej_val")
    with pytest.raises(ValueError, match="population_store"):
        train(bogus)
