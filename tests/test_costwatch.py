"""costwatch (PR 13): the compiled cost/memory ledger must be pure
metadata — ``program_cost`` events with the flat ledger schema riding
the telemetry dispatch tail, ``cost_ledger()`` priced off the
shardcheck inventory without executing anything, roofline math matching
a host-f64 hand reference, ``tools/costview`` budget gates with
tracedump-style exit codes, and the ``client_chunk: auto`` calibration
path resolving bit-exact against the same constant set by hand (with a
LOUD heuristic fallback on a cache miss)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import fed_avg_config
from distributed_learning_simulator_tpu.training import _build_task, train
from distributed_learning_simulator_tpu.util.calibration import (
    save_calibration_entry,
    session_calibration_key,
)
from distributed_learning_simulator_tpu.util.costwatch import (
    LEDGER_FIELDS,
    cost_summary,
    hlo_op_histogram,
    merge_ledgers,
    normalize_cost,
    roofline,
)
from tools.costview import attribute, check_budget, chip_tables, load_trace
from tools.costview.__main__ import main as costview_main


def _config(rounds, save_dir, telemetry=None, **overrides):
    config = fed_avg_config(
        executor="spmd",
        worker_number=overrides.pop("worker_number", 4),
        round=rounds,
        batch_size=32,
        epoch=1,
        save_dir=save_dir,
        dataset_kwargs={"train_size": 64, "val_size": 16, "test_size": 32},
        **overrides,
    )
    if telemetry is not None:
        config.telemetry = telemetry
    config.load_config_and_process()
    return config


def _session(config):
    from distributed_learning_simulator_tpu.parallel.spmd import (
        SpmdFedAvgSession,
    )

    ctx = _build_task(config)
    return SpmdFedAvgSession(
        ctx.config,
        ctx.dataset_collection,
        ctx.model_ctx,
        ctx.engine,
        ctx.practitioners,
    )


def _trace_path(save_dir):
    return os.path.join(save_dir, "server", "trace.jsonl")


def _run_one_round(session, seed=0):
    """The bench/autotune measurement seam: one round of the session's
    own round program, host-fetched leaves returned for comparison."""
    global_params = jax.device_put(
        session.engine.init_params(session.config.seed), session._replicated
    )
    _, weights, rngs, sel_idx = session._prepare_round_inputs(
        1, jax.random.PRNGKey(seed)
    )
    if sel_idx is not None:
        global_params, metrics = session._round_fn(
            global_params, weights, rngs, sel_idx
        )
    else:
        global_params, metrics = session._round_fn(global_params, weights, rngs)
    return [np.asarray(leaf) for leaf in jax.tree.leaves(global_params)]


# ---------------------------------------------------------------- ledger
def test_cost_summary_schema_on_compiled_program():
    """AOT-compiled matmul prices through the full ledger schema with
    positive flops/bytes, and normalize_cost survives every shape XLA
    returns (dict, one-element list, junk)."""
    fn = jax.jit(lambda a, b: (a @ b).sum())
    arg = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    row = cost_summary(fn.lower(arg, arg).compile())
    assert set(LEDGER_FIELDS) <= set(row)
    assert row["flops"] > 0
    assert row["bytes_accessed"] > 0
    assert all(isinstance(row[field], float) for field in LEDGER_FIELDS)
    # both wire shapes of cost_analysis() normalize identically
    as_dict = normalize_cost({"flops": 8.0, "bytes accessed": 4.0})
    as_list = normalize_cost([{"flops": 8.0, "bytes accessed": 4.0}])
    assert as_dict == as_list == {"flops": 8.0, "bytes_accessed": 4.0}
    assert normalize_cost(None) == {"flops": 0.0, "bytes_accessed": 0.0}
    assert normalize_cost([]) == {"flops": 0.0, "bytes_accessed": 0.0}
    # merge_ledgers sums field-wise and ignores extra keys
    total = merge_ledgers([row, row])
    assert total["flops"] == pytest.approx(2 * row["flops"])


def test_hlo_op_histogram_names_op_families():
    """The opcode histogram over real optimized HLO: rows carry
    op/count/output_bytes, sorted by output bytes descending — the view
    that names the top consumer behind a low MFU."""
    fn = jax.jit(lambda a, b: jnp.tanh(a @ b))
    arg = jax.ShapeDtypeStruct((32, 32), jnp.float32)
    hist = hlo_op_histogram(fn.lower(arg, arg).compile().as_text())
    assert hist, "histogram empty on real HLO"
    for row in hist:
        assert set(row) == {"op", "count", "output_bytes"}
        assert row["count"] >= 1
    byte_counts = [row["output_bytes"] for row in hist]
    assert byte_counts == sorted(byte_counts, reverse=True)
    assert hlo_op_histogram("", top=3) == []
    assert len(hlo_op_histogram("\n".join([""] * 5) or "x", top=1)) <= 1


def test_roofline_matches_host_reference():
    """Roofline math vs an explicit host-f64 hand computation on a v5e
    shape (hbm-bound), a compute-bound shape, and the no-tables case."""
    peak, bw = 197e12, 0.82e12
    flops, bytes_accessed, seconds = 4e12, 2e10, 0.05
    out = roofline(flops, bytes_accessed, seconds, peak, bw)
    intensity = flops / bytes_accessed  # 200.0
    ridge = peak / bw  # ~240.2
    attainable = min(peak, intensity * bw)  # 164e12, hbm roof
    assert out["arithmetic_intensity"] == pytest.approx(intensity)
    assert out["ridge_intensity"] == pytest.approx(ridge)
    assert out["bound_by"] == "hbm"
    assert out["roofline_flops_per_s"] == pytest.approx(attainable)
    assert out["roofline_mfu"] == pytest.approx(attainable / peak)
    assert out["achieved_flops_per_s"] == pytest.approx(flops / seconds)
    assert out["achieved_mfu"] == pytest.approx(flops / seconds / peak)
    assert out["fraction_of_roofline"] == pytest.approx(
        (flops / seconds) / attainable
    )
    # compute-bound: intensity above the ridge caps at peak
    out = roofline(1e15, 1e9, peak_flops=peak, hbm_bandwidth=bw)
    assert out["bound_by"] == "compute"
    assert out["roofline_flops_per_s"] == pytest.approx(peak)
    assert out["roofline_mfu"] == pytest.approx(1.0)
    # no chip tables: classification is honest, never a guess
    out = roofline(1e12, 1e9)
    assert out["bound_by"] == "unknown"
    assert out["roofline_mfu"] == 0.0
    assert "achieved_mfu" not in out


def test_chip_tables_longest_prefix_and_unknown():
    from tools.costview import TraceError

    peak, bw = chip_tables("TPU v5 lite", count=4)
    assert peak == pytest.approx(4 * 197e12)
    assert bw == pytest.approx(4 * 0.82e12)
    with pytest.raises(TraceError):
        chip_tables("GPU H100")


# --------------------------------------------------- trace round-trip
def test_trace_roundtrip_cost_events_and_costview(tmp_session_dir):
    """Telemetry-on run → program_cost events + dispatch_call spans in
    the trace → costview attribution with the full budget surface; the
    capture_cost/capture_hbm knobs gate the records off without touching
    the trajectory (bit-exact params either way)."""
    r_on = train(_config(rounds=2, save_dir="on", telemetry={"enabled": True}))
    r_off = train(
        _config(
            rounds=2,
            save_dir="nocost",
            telemetry={
                "enabled": True,
                "capture_cost": False,
                "capture_hbm": False,
            },
        )
    )
    # cost capture is observability only: trajectories identical
    for rn in r_on["performance"]:
        assert (
            r_on["performance"][rn]["test_accuracy"]
            == r_off["performance"][rn]["test_accuracy"]
        ), rn

    records = load_trace(_trace_path("on"))
    costs = [
        r for r in records if r.get("ev") == "event" and r["kind"] == "program_cost"
    ]
    calls = [
        r for r in records if r.get("ev") == "span" and r["kind"] == "dispatch_call"
    ]
    assert costs, "no program_cost events captured"
    assert calls, "no dispatch_call spans captured"
    for row in costs:
        assert set(LEDGER_FIELDS) <= set(row), row
        assert row["program"]
    assert {r["program"] for r in costs} <= {r["program"] for r in calls}
    assert all(r["dur"] >= 0 for r in calls)

    # the capture-off trace carries NO cost/hbm records but still counts
    nocost = load_trace(_trace_path("nocost"))
    assert not [r for r in nocost if r.get("kind") in ("program_cost", "hbm")]
    assert [r for r in nocost if r.get("kind") == "dispatch_call"]

    peak, bw = chip_tables("TPU v5e", count=1)
    attribution = attribute(records, peak_flops=peak, hbm_bandwidth=bw)
    budget = attribution["budget"]
    for key in (
        "programs_total",
        "flops_total",
        "bytes_accessed_total",
        "temp_bytes",
        "peak_hbm_bytes",
        "rounds_total",
        "round_seconds_total",
        "device_seconds_total",
        "host_gap_seconds_total",
        "host_gap_fraction",
    ):
        assert key in budget, key
    assert budget["programs_total"] >= 1
    assert budget["flops_total"] > 0
    assert budget["rounds_total"] == 2
    assert budget["round_seconds_total"] >= budget["device_seconds_total"]
    for row in attribution["programs"].values():
        assert row["bound_by"] in ("compute", "hbm", "unknown")
        assert "roofline_mfu" in row
    # the budget gate surface accepts generous bounds, rejects tight ones
    assert not check_budget(attribution, ["temp_bytes<=900000000000"])
    violations = check_budget(attribution, ["flops_total<=1"])
    assert violations and "flops_total" in violations[0]


def test_session_cost_ledger_prices_shardcheck_inventory(tmp_session_dir):
    """``session.cost_ledger()`` prices every program in the shardcheck
    inventory via abstract AOT compiles — rows carry the ledger schema
    with positive flops, and NOTHING dispatches (counters stay 0)."""
    session = _session(_config(rounds=1, save_dir="ledger"))
    ledger = session.cost_ledger()
    assert ledger, "empty ledger on an SPMD session"
    for name, row in ledger.items():
        assert set(LEDGER_FIELDS) <= set(row), name
    assert any(row["flops"] > 0 for row in ledger.values())
    assert session.dispatch_count == 0
    assert session.host_sync_count == 0


# -------------------------------------------------------- costview CLI
def _write_cost_trace(path, temp_bytes):
    from distributed_learning_simulator_tpu.util.telemetry import TraceRecorder

    rec = TraceRecorder(enabled=True, path=path, meta={"tool": "test"})
    rec.event(
        "program_cost",
        program="train_round",
        flops=1e9,
        bytes_accessed=1e7,
        argument_bytes=4e5,
        output_bytes=2e5,
        temp_bytes=temp_bytes,
        generated_code_bytes=1e4,
    )
    rec.span_record("dispatch_call", 0.02, program="train_round")
    rec.span_record("round", 0.05, round=1)
    rec.event("hbm", round=1, bytes_in_use=5e8, peak_bytes_in_use=6e8)
    rec.close()
    return path


def test_costview_cli_exit_codes(tmp_path, capsys):
    """Exit-code contract mirrors tracedump: 0 clean, 1 on a violated
    budget or a --diff cost regression, 2 on usage errors."""
    trace = _write_cost_trace(str(tmp_path / "trace.jsonl"), temp_bytes=16400)
    assert costview_main([trace, "--chip", "TPU v5e"]) == 0
    out = capsys.readouterr().out
    assert "train_round" in out
    assert "peak_hbm" in out
    assert costview_main([trace, "--assert-budget", "temp_bytes<=20000"]) == 0
    assert costview_main([trace, "--assert-budget", "temp_bytes<=1"]) == 1
    assert (
        costview_main([trace, "--assert-budget", "peak_hbm_bytes<=100"]) == 1
    )
    # unknown budget key / unknown chip / unreadable trace: usage errors
    assert costview_main([trace, "--assert-budget", "bogus_key<=1"]) == 2
    assert costview_main([trace, "--chip", "GPU H100"]) == 2
    assert costview_main([str(tmp_path / "missing.jsonl")]) == 2
    # --diff: rising temp bytes is a regression (exit 1), shrinking is not
    baseline = _write_cost_trace(str(tmp_path / "base.jsonl"), temp_bytes=99)
    assert costview_main([trace, "--diff", baseline]) == 1
    assert costview_main([baseline, "--diff", trace]) == 0
    # json format round-trips with the budget surface attached
    assert costview_main([trace, "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["budget"]["temp_bytes"] == 16400
    assert payload["budget"]["peak_hbm_bytes"] == 6e8
    assert payload["budget_failures"] == []


# ------------------------------------------------------------- autotune
def test_pick_winner_argmin_with_tie_toward_smaller_chunk():
    from tools.autotune import pick_winner

    assert pick_winner({1: 0.5, 2: 0.5, 4: 0.4}) == 4
    assert pick_winner({4: 0.25, 2: 0.25}) == 2  # tie -> smaller chunk
    assert pick_winner({8: 0.1}) == 8


def test_autotune_sweep_deterministic_with_injected_timer(
    tmp_session_dir, tmp_path
):
    """Same seed + same (injected, wall-clock-free) timer → the SAME
    entry twice, written under the canonical calibration key."""
    from tools.autotune import run_sweep

    def factory_for(tag):
        def config_factory(chunk):
            return _config(
                rounds=1,
                save_dir=f"at_{tag}_{chunk}",
                algorithm_kwargs={"client_chunk": chunk},
            )

        return config_factory

    def fake_leg(session, seed, rounds, warmup):
        # deterministic function of the leg's chunk; also pins that the
        # factory's chunk actually reached the session
        assert session.client_chunk in (1, 2)
        return 0.3 / float(session.client_chunk)

    results = [
        run_sweep(
            factory_for(tag),
            candidates=[1, 2],
            rounds=2,
            warmup=1,
            seed=0,
            output=str(tmp_path / "calibration.json"),
            time_leg=fake_leg,
        )
        for tag in ("a", "b")
    ]
    assert results[0]["key"] == results[1]["key"]
    assert results[0]["entry"] == results[1]["entry"]
    assert results[0]["entry"]["client_chunk"] == 2
    assert results[0]["entry"]["legs"] == {"1": 0.3, "2": 0.15}
    with open(tmp_path / "calibration.json", encoding="utf8") as f:
        blob = json.load(f)
    assert blob["entries"][results[0]["key"]]["client_chunk"] == 2


def test_client_chunk_auto_bit_exact_vs_hand_constant(
    tmp_session_dir, tmp_path
):
    """The acceptance pin: ``client_chunk: auto`` resolving to N from
    the calibration cache is BIT-EXACT vs ``client_chunk: N`` set by
    hand — same resolved chunk, identical round outputs."""
    hand = _session(
        _config(
            rounds=1, save_dir="hand", algorithm_kwargs={"client_chunk": 2}
        )
    )
    cache = str(tmp_path / "calibration.json")
    save_calibration_entry(
        session_calibration_key(hand), {"client_chunk": 2}, cache
    )
    auto = _session(
        _config(
            rounds=1,
            save_dir="auto",
            algorithm_kwargs={
                "client_chunk": "auto",
                "calibration_path": cache,
            },
        )
    )
    assert auto.client_chunk == hand.client_chunk == 2
    for a, b in zip(_run_one_round(hand), _run_one_round(auto)):
        np.testing.assert_array_equal(a, b)


def test_client_chunk_auto_miss_falls_back_to_default(
    tmp_session_dir, tmp_path
):
    """A cache miss resolves to 0 — the exact hand-set-default heuristic
    path, so ``auto`` without calibration behaves like an unset knob."""
    session = _session(
        _config(
            rounds=1,
            save_dir="miss",
            algorithm_kwargs={
                "client_chunk": "auto",
                "calibration_path": str(tmp_path / "nope.json"),
            },
        )
    )
    assert session._client_chunk_auto is True
    assert session.client_chunk == 0
    default = _session(_config(rounds=1, save_dir="unset"))
    assert session.client_chunk == default.client_chunk


@pytest.mark.slow
def test_autotune_calibration_end_to_end(tmp_session_dir, tmp_path):
    """Real (wall-clock) sweep on the tiny shape: writes a winner entry
    an ``auto`` session then resolves — the zero→calibrated loop."""
    from tools.autotune import run_sweep

    def config_factory(chunk):
        return _config(
            rounds=1,
            save_dir=f"e2e_{chunk}",
            algorithm_kwargs={"client_chunk": chunk},
        )

    cache = str(tmp_path / "calibration.json")
    result = run_sweep(
        config_factory,
        candidates=[1, 2],
        rounds=1,
        warmup=1,
        seed=0,
        output=cache,
        trace_path=str(tmp_path / "sweep_trace.jsonl"),
    )
    winner = result["entry"]["client_chunk"]
    assert winner in (1, 2)
    spans = [
        r
        for r in load_trace(str(tmp_path / "sweep_trace.jsonl"))
        if r.get("kind") == "autotune_leg"
    ]
    assert len(spans) == 2
    session = _session(
        _config(
            rounds=1,
            save_dir="e2e_auto",
            algorithm_kwargs={
                "client_chunk": "auto",
                "calibration_path": cache,
            },
        )
    )
    assert session.client_chunk == winner
