"""Short-sequence packed-QKV Pallas kernel: exactness vs explicit math.

Runs under the Pallas TPU interpreter on the CPU test mesh
(``DLS_TPU_FUSED_ATTN=interpret``) — same kernel the chip compiles, minus
Mosaic.  Shapes cover the MXU batch-stacking (bb=2 at S=64), the
non-multiple-of-16 padding path, Dh=128 heads, and the model-level
integration through ``models.attention.FusedSelfAttention``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_learning_simulator_tpu.ops import short_attention as sa


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    monkeypatch.setenv("DLS_TPU_FUSED_ATTN", "interpret")


def reference(qkv, num_heads, kv_mask=None):
    b, s, width = qkv.shape
    d = width // 3
    dh = d // num_heads
    q, k, v = jnp.split(qkv, 3, -1)

    def heads(t):
        return t.reshape(b, s, num_heads, dh).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q * (dh**-0.5), k)
    if kv_mask is not None:
        logits = jnp.where(kv_mask[:, None, None, :] > 0, logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return out.transpose(0, 2, 1, 3).reshape(b, s, d)


CASES = [
    (4, 64, 6, 64),   # ViT-small shape; bb=2 MXU stacking
    (3, 50, 6, 64),   # row padding (50 -> 64) + odd batch
    (2, 128, 4, 128),  # Dh = 128 (full lane), bb=1
    (5, 64, 6, 64),   # odd batch at stackable S
]


@pytest.mark.parametrize("b,s,h,dh", CASES)
@pytest.mark.parametrize("with_mask", [False, True])
def test_forward_matches_reference(b, s, h, dh, with_mask):
    rng = np.random.default_rng(0)
    d = h * dh
    assert sa.short_eligible(s, d, h)
    qkv = jnp.asarray(rng.normal(size=(b, s, 3 * d)), jnp.float32)
    mask = None
    if with_mask:
        mask = jnp.asarray(rng.integers(0, 2, (b, s)), jnp.float32)
        mask = mask.at[:, 0].set(1)  # no all-masked rows
    out = sa.short_attention(qkv, h, kv_mask=mask)
    ref = reference(qkv, h, kv_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-6)


@pytest.mark.parametrize("b,s,h,dh", CASES[:2])
def test_gradients_match_reference(b, s, h, dh):
    rng = np.random.default_rng(1)
    d = h * dh
    qkv = jnp.asarray(rng.normal(size=(b, s, 3 * d)), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (b, s)), jnp.float32)
    mask = mask.at[:, 0].set(1)

    gk = jax.grad(
        lambda t: jnp.sum(jnp.sin(sa.short_attention(t, h, kv_mask=mask)))
    )(qkv)
    gr = jax.grad(
        lambda t: jnp.sum(jnp.sin(reference(t, h, kv_mask=mask)))
    )(qkv)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=3e-6)


def test_vmap_batches_the_grid():
    """The SPMD sessions vmap client chunks over the model — the kernel
    must batch (pallas adds a leading grid dim)."""
    rng = np.random.default_rng(2)
    qkv = jnp.asarray(rng.normal(size=(2, 4, 64, 3 * 384)), jnp.float32)
    out = jax.vmap(lambda t: sa.short_attention(t, 6))(qkv)
    ref = jnp.stack([reference(qkv[i], 6) for i in range(2)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-6)


def test_eligibility_gate():
    assert not sa.short_eligible(64, 100, 5)  # Dh=20: not a lane fraction
    assert not sa.short_eligible(2048, 384, 6)  # long: fused kernel's turf
    assert sa.short_eligible(300, 512, 4)  # BERT-ish: Dh=128


def test_model_integration_matches_xla_path(monkeypatch):
    """FusedSelfAttention routes through the kernel when eligible and the
    XLA dot_general path when killed — both must agree."""
    from distributed_learning_simulator_tpu.models.attention import (
        FusedSelfAttention,
    )

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 64, 384)), jnp.float32)
    m = FusedSelfAttention(num_heads=6)
    params = m.init(jax.random.PRNGKey(0), x)
    out_kernel = m.apply(params, x)
    monkeypatch.setenv("DLS_TPU_FUSED_ATTN", "off")
    out_xla = m.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(out_kernel), np.asarray(out_xla), atol=3e-6
    )
