"""FedOBD phase-2 optimizer continuation on the SPMD executor (VERDICT r2
item 5): ``reuse_learning_rate`` semantics — the per-slot optimizer states
(momentum trace + schedule position) carry from the END of phase 1 across
the phase switch and through every phase-2 epoch, matching the threaded
executor (reference ``util/model.py:6-23``; threaded
``Trainer.load_parameter_dict(reuse_learning_rate=True)``)."""

import jax
import numpy as np

from distributed_learning_simulator_tpu.parallel.spmd_obd import SpmdFedOBDSession
from distributed_learning_simulator_tpu.training import _build_task

from conftest import fed_avg_config


def _counts(opt_state) -> list[int]:
    """All schedule-count leaves (int32 scalars per slot) in the state."""
    return [
        np.asarray(leaf)
        for leaf in jax.tree.leaves(opt_state)
        if np.asarray(leaf).dtype == np.int32
    ]


def _real_batches(session) -> np.ndarray:
    """Per-slot count of NONEMPTY batches: all-padding batches (shorter
    clients share the longest client's batch count; zero-weight padding
    slots are all padding) are true no-ops in the engine — they advance
    neither momentum nor the schedule (cross-executor parity,
    ``engine/engine.py::train_step_fn``)."""
    sizes = np.asarray(session._dataset_sizes)
    return np.ceil(sizes / session.config.batch_size).astype(np.int32)


def _make_session(tmp_session_dir, rounds: int, phase2_epochs: int):
    config = fed_avg_config(
        distributed_algorithm="fed_obd",
        executor="spmd",
        worker_number=4,
        round=rounds,
        epoch=1,
        batch_size=16,
        dataset_kwargs={"train_size": 128, "val_size": 16, "test_size": 32},
        algorithm_kwargs={
            "dropout_rate": 0.3,
            "second_phase_epoch": phase2_epochs,
            "early_stop": False,
        },
        endpoint_kwargs={"server": {"weight": 0.01}, "worker": {"weight": 0.01}},
        save_dir=str(tmp_session_dir / "obd_carry"),
    )
    ctx = _build_task(config)
    return (
        SpmdFedOBDSession(
            ctx.config,
            ctx.dataset_collection,
            ctx.model_ctx,
            ctx.engine,
            ctx.practitioners,
        ),
        ctx,
    )


def test_phase2_schedule_position_continues(tmp_session_dir):
    phase2_epochs = 3
    session, ctx = _make_session(tmp_session_dir, rounds=1, phase2_epochs=phase2_epochs)
    result = session.run()
    assert result["performance"]

    counts = _counts(session._opt_state_s)
    assert counts, "optimizer state has no schedule count leaf"
    # phase 1: 1 round x 1 epoch of each slot's REAL batches (optimizer
    # rebuilt per round); phase 2: 3 epochs CONTINUE the same state ->
    # final count = (1 + 3) x real_batches per slot.  A phase-2 restart
    # (the retired deviation) would leave 1 x real_batches.
    expected = (1 + phase2_epochs) * _real_batches(session)
    for count in counts:
        assert np.all(count == expected), (count, expected)


def test_phase2_momentum_carries_across_switch(tmp_session_dir):
    """The optimizer state ENTERING the first phase-2 step is phase 1's
    final state — non-None, nonzero momentum traces, nonzero schedule
    count.  A phase-2 restart would call the program with None (or fresh
    zeros), which this intercept detects directly."""
    session, ctx = _make_session(tmp_session_dir, rounds=2, phase2_epochs=1)
    original_build = session._build_phase_fn
    captured: dict = {}

    def build(phase_two: bool):
        fn = original_build(phase_two=phase_two)
        if not phase_two:
            return fn

        def wrapped(global_params, weights, rngs, bcast_rng, opt_state_s=None):
            if "entry" not in captured:
                captured["entry"] = (
                    None
                    if opt_state_s is None
                    else jax.tree.map(np.asarray, opt_state_s)
                )
            return fn(global_params, weights, rngs, bcast_rng, opt_state_s)

        return wrapped

    session._build_phase_fn = build
    session.run()
    entry = captured["entry"]
    assert entry is not None, "phase 2 was invoked without a carried state"
    counts = _counts(entry)
    real = _real_batches(session) > 0  # padding slots never step
    assert counts and all(np.all(c[real] > 0) for c in counts)
    traces = [
        np.asarray(leaf)
        for leaf in jax.tree.leaves(entry)
        if np.asarray(leaf).dtype == np.float32 and np.asarray(leaf).ndim > 1
    ]
    assert traces
    assert all(np.abs(t).max() > 0 for t in traces)


def test_phase2_trajectory_matches_threaded(tmp_session_dir):
    """Same config through both executors: loose final-metric agreement now
    that BOTH carry optimizer state across the phase switch (different rng
    streams, same algorithm)."""
    from distributed_learning_simulator_tpu.config import (
        DistributedTrainingConfig,
    )
    from distributed_learning_simulator_tpu.training import train

    def run(executor: str):
        config = fed_avg_config(
            distributed_algorithm="fed_obd",
            executor=executor,
            worker_number=2,
            round=2,
            epoch=1,
            batch_size=16,
            dataset_kwargs={"train_size": 256, "val_size": 16, "test_size": 64},
            algorithm_kwargs={
                "dropout_rate": 0.3,
                "second_phase_epoch": 2,
                "early_stop": False,
            },
            endpoint_kwargs={
                "server": {"weight": 0.001},
                "worker": {"weight": 0.001},
            },
            save_dir=str(tmp_session_dir / f"obd_{executor}"),
        )
        result = train(config)
        stat = result["performance"]
        return stat[max(stat)]

    spmd = run("spmd")
    threaded = run("sequential")
    assert np.isfinite(spmd["test_loss"]) and np.isfinite(threaded["test_loss"])
    assert abs(spmd["test_accuracy"] - threaded["test_accuracy"]) < 0.35


def test_phase2_resume_restores_optimizer_states(tmp_session_dir):
    """opt_state.npz: a resume landing mid-phase-2 on the aggregate the
    states were saved with CONTINUES momentum + schedule position (the
    SURVEY §5 'per-client opt state' checkpoint); counts keep growing from
    the restored value instead of restarting."""
    session, ctx = _make_session(tmp_session_dir, rounds=1, phase2_epochs=1)
    session.run()
    steps = _real_batches(session)
    # 1 phase-1 round + 1 phase-2 epoch, states saved tagged with the final
    # aggregate (key 2)
    final_counts = _counts(session._opt_state_s)
    assert all(np.all(c == 2 * steps) for c in final_counts)

    # a new session with a LARGER phase-2 budget resumes from the same
    # record: replay keeps both aggregates, lands in phase 2 tick 1, and
    # the saved states (tag == last kept aggregate) are restored
    config2 = session.config.replace(save_dir=str(tmp_session_dir / "resumed"))
    config2.algorithm_kwargs = dict(
        config2.algorithm_kwargs,
        second_phase_epoch=3,
        resume_dir=session.config.save_dir,
    )
    from distributed_learning_simulator_tpu.parallel.spmd_obd import (
        SpmdFedOBDSession,
    )

    resumed = SpmdFedOBDSession(
        config2,
        ctx.dataset_collection,
        ctx.model_ctx,
        ctx.engine,
        ctx.practitioners,
    )
    result = resumed.run()
    assert resumed._resumed_opt_state is not None, "states were not restored"
    # continued: (1 phase-1 + 1 restored + 2 new phase-2 epochs) x steps
    counts = _counts(resumed._opt_state_s)
    assert all(np.all(c == 4 * steps) for c in counts), counts
    assert set(result["performance"]) == {1, 2, 3, 4}
