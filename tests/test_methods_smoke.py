"""Per-method 1-round smoke matrix (mirrors the reference's ``test.sh`` +
``other_method_test.sh`` — SURVEY.md §4), on tiny synthetic data."""

import pytest

from distributed_learning_simulator_tpu.config import DistributedTrainingConfig
from distributed_learning_simulator_tpu.training import train

# heavy e2e: excluded from the tier-1 CI budget (-m 'not slow'),
# still runs in a plain `pytest tests/` (see tests/conftest.py)
pytestmark = pytest.mark.slow


def tiny_config(algo: str, **overrides) -> DistributedTrainingConfig:
    config = DistributedTrainingConfig(
        dataset_name="MNIST",
        model_name="LeNet5",
        distributed_algorithm=algo,
        optimizer_name="SGD",
        worker_number=2,
        batch_size=32,
        round=1,
        epoch=1,
        learning_rate=0.05,
        # this file IS the threaded-executor parity matrix (auto now
        # resolves to spmd; the SPMD matrix lives in test_spmd_methods +
        # test_executor_matrix)
        executor="sequential",
        dataset_kwargs={"train_size": 128, "val_size": 32, "test_size": 32},
    )
    for key, value in overrides.items():
        setattr(config, key, value)
    return config


def run(config) -> dict:
    result = train(config)
    assert result["performance"], "no round stats recorded"
    for stat in result["performance"].values():
        assert 0.0 <= stat["test_accuracy"] <= 1.0
    return result


def test_fed_paq(tmp_session_dir):
    result = run(tiny_config("fed_paq"))
    baseline = run(tiny_config("fed_avg"))
    # byte accounting counts at the wire: quantized uploads must report
    # compressed sizes, not the dequantized full-precision dicts
    assert (
        result["performance"][1]["received_mb"]
        < 0.5 * baseline["performance"][1]["received_mb"]
    )


def test_fed_dropout_avg(tmp_session_dir):
    run(
        tiny_config(
            "fed_dropout_avg", algorithm_kwargs={"dropout_rate": 0.3}
        )
    )


def test_sign_sgd(tmp_session_dir):
    config = tiny_config("sign_SGD", distribute_init_parameters=False)
    result = train(config)
    # per-step method: one final test metric recorded at exit
    assert 0.0 <= result["performance"][1]["test_accuracy"] <= 1.0


def test_single_model_afd(tmp_session_dir):
    run(tiny_config("single_model_afd", algorithm_kwargs={"dropout_rate": 0.3}))


def test_fed_obd(tmp_session_dir):
    config = tiny_config(
        "fed_obd",
        round=2,
        algorithm_kwargs={"second_phase_epoch": 1, "dropout_rate": 0.5},
        endpoint_kwargs={"server": {"weight": 0.01}, "worker": {"weight": 0.01}},
    )
    run(config)


def test_fed_obd_early_stop(tmp_session_dir):
    """early_stop threads through the phase driver from round 1 (empty
    performance_stat must not crash the plateau test)."""
    config = tiny_config(
        "fed_obd",
        round=2,
        algorithm_kwargs={
            "second_phase_epoch": 1,
            "dropout_rate": 0.5,
            "early_stop": True,
        },
        endpoint_kwargs={"server": {"weight": 0.01}, "worker": {"weight": 0.01}},
    )
    run(config)


def test_fed_obd_sq(tmp_session_dir):
    """fed_obd with StochasticQuant endpoints instead of NNADQ (reference
    ``method/fed_obd/__init__.py:16-22``)."""
    config = tiny_config(
        "fed_obd_sq",
        round=2,
        algorithm_kwargs={"second_phase_epoch": 1, "dropout_rate": 0.5},
    )
    run(config)


def test_fed_gcn(tmp_session_dir):
    """FedGCN variant: feature sharing forced on even when the config says
    otherwise (reference ``method/fed_gcn/worker.py:4-7``)."""
    config = DistributedTrainingConfig(
        dataset_name="Cora",
        model_name="TwoGCN",
        distributed_algorithm="fed_gcn",
        executor="sequential",
        worker_number=2,
        round=1,
        epoch=1,
        learning_rate=0.01,
        dataset_kwargs={},
        algorithm_kwargs={"share_feature": False},
    )
    run(config)


def test_multiround_shapley(tmp_session_dir):
    config = tiny_config("multiround_shapley_value", worker_number=3)
    result = run(config)
    assert "sv" in result
    assert set(result["sv"]) == {1}
    assert len(result["sv"][1]) == 3


def test_gtg_shapley(tmp_session_dir):
    config = tiny_config("GTG_shapley_value", worker_number=3)
    result = run(config)
    assert "sv" in result
    assert set(result["sv"]) == {1}
    assert len(result["sv"][1]) == 3


def test_fed_gnn(tmp_session_dir):
    config = DistributedTrainingConfig(
        dataset_name="Cora",
        model_name="TwoGCN",
        distributed_algorithm="fed_gnn",
        executor="sequential",
        worker_number=2,
        round=1,
        epoch=1,
        learning_rate=0.01,
        dataset_kwargs={},
        algorithm_kwargs={"share_feature": True, "edge_drop_rate": 0.5},
    )
    run(config)


def test_random_selection(tmp_session_dir):
    config = tiny_config(
        "fed_avg", worker_number=3, round=2,
        algorithm_kwargs={"random_client_number": 2},
    )
    run(config)
