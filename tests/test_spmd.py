"""SPMD fast-path tests on the virtual 8-device CPU mesh."""

import numpy as np

from distributed_learning_simulator_tpu.config import DistributedTrainingConfig
from distributed_learning_simulator_tpu.training import train


def spmd_config(**overrides) -> DistributedTrainingConfig:
    config = DistributedTrainingConfig(
        dataset_name="MNIST",
        model_name="LeNet5",
        distributed_algorithm="fed_avg",
        executor="spmd",
        worker_number=10,
        batch_size=32,
        round=2,
        epoch=1,
        learning_rate=0.05,
        dataset_kwargs={"train_size": 320, "val_size": 32, "test_size": 64},
    )
    for key, value in overrides.items():
        setattr(config, key, value)
    return config


def test_spmd_fed_avg_runs_on_mesh(tmp_session_dir):
    import jax

    assert len(jax.devices()) == 8  # conftest forced the virtual mesh
    result = train(spmd_config())
    assert len(result["performance"]) == 2
    for stat in result["performance"].values():
        assert 0.0 <= stat["test_accuracy"] <= 1.0


def test_spmd_learns_and_selection(tmp_session_dir):
    result = train(
        spmd_config(
            round=3,
            epoch=2,
            algorithm_kwargs={"random_client_number": 5},
            dataset_kwargs={"train_size": 1280, "val_size": 64, "test_size": 128},
        )
    )
    best = max(s["test_accuracy"] for s in result["performance"].values())
    assert best > 0.5


def test_put_sharded_single_process_matches_device_put():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_learning_simulator_tpu.parallel.mesh import (
        initialize_multihost,
        make_mesh,
        put_sharded,
    )

    initialize_multihost()  # no-op without a coordinator
    mesh = make_mesh()
    data = {"a": np.arange(mesh.shape["clients"] * 4, dtype=np.float32).reshape(
        mesh.shape["clients"], 4
    )}
    out = put_sharded(data, NamedSharding(mesh, P("clients")))
    np.testing.assert_array_equal(np.asarray(out["a"]), data["a"])
    assert out["a"].sharding.spec == P("clients")


def test_spmd_matches_threaded_fed_avg_statistically():
    """Same config through both executors: different rng streams, same
    algorithm — after two rounds the test metrics must land close."""
    import numpy as np

    from distributed_learning_simulator_tpu.config import DistributedTrainingConfig
    from distributed_learning_simulator_tpu.training import train

    def run(executor):
        config = DistributedTrainingConfig(
            dataset_name="MNIST",
            model_name="LeNet5",
            distributed_algorithm="fed_avg",
            executor=executor,
            worker_number=4,
            batch_size=32,
            round=2,
            epoch=1,
            learning_rate=0.05,
            dataset_kwargs={"train_size": 512, "val_size": 64, "test_size": 128},
        )
        return train(config)["performance"][2]

    threaded = run("sequential")  # auto now resolves to spmd for built-ins
    spmd = run("spmd")
    assert abs(threaded["test_accuracy"] - spmd["test_accuracy"]) < 0.2
    assert abs(threaded["test_loss"] - spmd["test_loss"]) < 0.5
