"""Config-reachable sequence parallelism: ``model_kwargs.sequence_parallel``
shards a long-context client model's sequence axis over an ("sp",) mesh —
the reference has NO model-sharding story at all (SURVEY.md §5); here it is
a YAML knob (the mesh is built in ``_build_task``, YAML carries the size).
"""

import numpy as np
import pytest

from distributed_learning_simulator_tpu.config import DistributedTrainingConfig
from distributed_learning_simulator_tpu.training import train

# heavy e2e: excluded from the tier-1 CI budget (-m 'not slow'),
# still runs in a plain `pytest tests/` (see tests/conftest.py)
pytestmark = pytest.mark.slow


def _config(**model_extra):
    return DistributedTrainingConfig(
        dataset_name="imdb",
        model_name="LongContextTransformer",
        distributed_algorithm="fed_avg",
        executor="auto",
        worker_number=2,
        batch_size=4,
        round=1,
        epoch=1,
        learning_rate=0.05,
        dataset_kwargs={
            "train_size": 16,
            "val_size": 4,
            "test_size": 8,
            "max_len": 64,
        },
        model_kwargs={
            "d_model": 32,
            "nhead": 4,
            "num_encoder_layer": 1,
            "max_len": 64,
            **model_extra,
        },
    )


def test_sequence_parallel_from_config_matches_unsharded():
    """Same seeds, same math: the sp=4 run's metrics equal the unsharded
    run's up to ring-accumulation float order (ring attention is exact).
    Both runs pin the threaded executor (this test validates the
    model-owned ``sp_mesh`` mode; the SPMD sp session has its own
    equivalence test below) — mixing executors would compare trajectories
    that differ by executor, not by sharding."""
    base_config = _config()
    base_config.executor = "sequential"
    base = train(base_config)
    sp_config = _config(sequence_parallel=4)
    sp_config.executor = "sequential"
    sp = train(sp_config)
    for key in ("test_loss", "test_accuracy"):
        np.testing.assert_allclose(
            sp["performance"][1][key], base["performance"][1][key], atol=2e-4
        )


def test_spmd_sequence_parallel_session_matches_client_axis_session():
    """fed_avg + sequence_parallel under executor spmd runs the dedicated
    SP session (whole mesh to each client's model, clients scanned).  At
    worker_number == n_slots both sessions consume the IDENTICAL rng
    stream, and ring attention is exact — so the two layouts must produce
    the same trajectory to float accumulation order."""
    base_config = _config()
    base_config.executor = "spmd"
    base_config.worker_number = 8
    base = train(base_config)

    sp_config = _config(sequence_parallel=4)
    sp_config.executor = "spmd"
    sp_config.worker_number = 8
    sp = train(sp_config)
    for key in ("test_loss", "test_accuracy"):
        np.testing.assert_allclose(
            sp["performance"][1][key], base["performance"][1][key], atol=2e-4
        )


def test_sequence_parallel_rejects_spmd_for_other_methods():
    config = _config(sequence_parallel=4)
    config.executor = "spmd"
    config.distributed_algorithm = "fed_paq"
    config.endpoint_kwargs = {"worker": {"quantization_level": 255}}
    with pytest.raises(ValueError, match="sequence_parallel"):
        train(config)


def test_sequence_parallel_ulysses_impl():
    result = train(_config(sequence_parallel=4, sp_impl="ulysses"))
    assert np.isfinite(result["performance"][1]["test_loss"])


def test_causal_lm_trains_and_matches_under_ring_sp():
    """CausalLMTransformer is a TRAINABLE zoo member (loss_type
    "causal_lm": next-token CE derived from the input tokens, any text
    dataset doubles as an LM corpus), and the round-4 causal ring path is
    config-reachable end to end: under sequence_parallel the loss does a
    ring boundary-token exchange + global-masked-mean reduction
    (psum_symmetric), so the sharded trajectory matches the unsharded one
    exactly."""

    def lm_config(**model_extra):
        config = _config(**model_extra)
        config.model_name = "CausalLMTransformer"
        config.model_kwargs = dict(config.model_kwargs, dropout_rate=0.0)
        config.round = 2
        return config

    base = train(lm_config())
    sp = train(lm_config(sequence_parallel=4))
    for round_number in (1, 2):
        for key in ("test_loss", "test_accuracy"):
            np.testing.assert_allclose(
                sp["performance"][round_number][key],
                base["performance"][round_number][key],
                atol=2e-4,
            )
    # perplexity is finite and improving-ish (sanity, not convergence)
    assert np.isfinite(base["performance"][2]["test_loss"])


def test_causal_lm_ulysses_matches_unsharded():
    """The causal path composes with BOTH sp implementations: Ulysses'
    post-all-to-all full-sequence attention supports causal directly, and
    the sharded LM loss is implementation-agnostic (ring boundary token +
    global masked mean)."""

    def lm_config(**model_extra):
        config = _config(**model_extra)
        config.model_name = "CausalLMTransformer"
        config.model_kwargs = dict(config.model_kwargs, dropout_rate=0.0)
        return config

    base = train(lm_config())
    uly = train(lm_config(sequence_parallel=4, sp_impl="ulysses"))
    for key in ("test_loss", "test_accuracy"):
        np.testing.assert_allclose(
            uly["performance"][1][key],
            base["performance"][1][key],
            atol=2e-4,
        )


def test_sequence_parallel_cross_executor_parity():
    """Sequence parallelism is EXECUTOR-invariant too: the threaded path
    (model-owned sp_mesh inside per-client jitted steps) and the SPMD SP
    session (session-owned shard_map, clients scanned) train identical
    fed_avg trajectories under the aligned rng streams."""
    spmd_config = _config(sequence_parallel=4)
    spmd_config.executor = "spmd"
    spmd_config.round = 2
    threaded_config = _config(sequence_parallel=4)
    threaded_config.executor = "sequential"
    threaded_config.round = 2
    spmd = train(spmd_config)
    threaded = train(threaded_config)
    for round_number in (1, 2):
        for key in ("test_loss", "test_accuracy"):
            np.testing.assert_allclose(
                spmd["performance"][round_number][key],
                threaded["performance"][round_number][key],
                rtol=0,
                atol=1e-5,
            )
